"""gRPC plumbing over runtime protobuf descriptors.

No generated ``*_pb2_grpc.py`` stubs exist (the image has bare protoc only,
see electionguard_tpu.publish.pb) — services and client stubs are built
directly from the service descriptors, so the .proto files remain the single
contract.  Mirrors the reference's transport settings: plaintext channels,
per-destination channel, 51 MB max message for trustee data planes and 2 KB
for registration (reference: RemoteTrusteeProxy.java:30,249-252,
RemoteKeyCeremonyProxy.java:27).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Callable

import grpc
from google.protobuf import message_factory

from electionguard_tpu.publish import pb

MAX_TRUSTEE_MESSAGE = 51 * 1000 * 1000   # key exchange / batch decrypt plane
MAX_REGISTRATION_MESSAGE = 2000          # registration plane

#: attempts per rpc on transient transport failure (UNAVAILABLE) — the
#: reference retries nothing (SURVEY.md §5.3); we retry the one status
#: that means "peer not reachable right now" so a guardian restart or a
#: slow dial-back doesn't kill a whole ceremony.  EGTPU_RPC_RETRIES=1
#: restores the reference's posture.
try:
    RPC_ATTEMPTS = max(1, int(os.environ.get("EGTPU_RPC_RETRIES", "3")))
except ValueError:
    import logging
    logging.getLogger("rpc_util").warning(
        "EGTPU_RPC_RETRIES=%r is not an integer; using 3",
        os.environ.get("EGTPU_RPC_RETRIES"))
    RPC_ATTEMPTS = 3
_RPC_RETRY_WAIT = 0.5
_RPC_CONNECT_WINDOW = 5.0   # max seconds a wait_for_ready retry may block


def _method_classes(method_desc):
    req = message_factory.GetMessageClass(method_desc.input_type)
    resp = message_factory.GetMessageClass(method_desc.output_type)
    return req, resp


def generic_service(service_name: str,
                    impls: dict[str, Callable]) -> grpc.GenericRpcHandler:
    """Build a generic handler for ``service_name`` from ``{method: fn}``
    where fn(request_msg, context) -> response_msg."""
    svc = pb.service_descriptor(service_name)
    handlers = {}
    for m in svc.methods:
        if m.name not in impls:
            raise ValueError(f"missing impl for {service_name}.{m.name}")
        req_cls, _ = _method_classes(m)
        handlers[m.name] = grpc.unary_unary_rpc_method_handler(
            impls[m.name],
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg: msg.SerializeToString())
    return grpc.method_handlers_generic_handler(svc.full_name, handlers)


class Stub:
    """Client stub for one service over one channel: ``stub.call(name, req)``."""

    def __init__(self, channel: grpc.Channel, service_name: str):
        svc = pb.service_descriptor(service_name)
        self._methods = {}
        for m in svc.methods:
            req_cls, resp_cls = _method_classes(m)
            self._methods[m.name] = channel.unary_unary(
                f"/{svc.full_name}/{m.name}",
                request_serializer=lambda msg: msg.SerializeToString(),
                response_deserializer=resp_cls.FromString)

    def call(self, method: str, request, timeout: float = 60.0):
        """One rpc with a TOTAL deadline of ``timeout`` seconds.

        UNAVAILABLE (transport-level) is retried with backoff while
        budget remains, up to RPC_ATTEMPTS.  Retries pass
        ``wait_for_ready`` so the channel actually re-dials a peer that
        is coming (back) up instead of failing fast inside gRPC's own
        reconnect backoff window — but each such wait is BOUNDED
        (``_RPC_CONNECT_WINDOW``) so a permanently-dead peer fails in
        seconds, not the whole deadline.  Safe because every service
        method is idempotent: the batch/exchange rpcs are pure functions
        of the request (plus fresh randomness), and both coordinators
        treat a same-identity re-registration as idempotent.
        """
        deadline = time.monotonic() + timeout
        attempt = 0
        while True:
            remaining = deadline - time.monotonic()
            wfr = attempt > 0
            per_try = max(0.001, min(remaining, _RPC_CONNECT_WINDOW)
                          if wfr else remaining)
            try:
                return self._methods[method](
                    request, timeout=per_try, wait_for_ready=wfr)
            except grpc.RpcError as e:
                attempt += 1
                code = e.code()
                # a DEADLINE on a BOUNDED connect-wait means "still not
                # reachable" — transient like UNAVAILABLE; a deadline on
                # a full-budget attempt is a real timeout
                transient = (code == grpc.StatusCode.UNAVAILABLE
                             or (wfr and per_try < remaining
                                 and code ==
                                 grpc.StatusCode.DEADLINE_EXCEEDED))
                wait = _RPC_RETRY_WAIT * attempt
                if (not transient or attempt >= RPC_ATTEMPTS
                        or deadline - time.monotonic() <= wait):
                    raise
                time.sleep(wait)


def group_constants_msg(group):
    """The coordinator's GroupConstants for registration responses."""
    return pb.msg("GroupConstants")(
        p=group.p.to_bytes(group.spec.p_bytes, "big"),
        q=group.q.to_bytes(group.spec.q_bytes, "big"),
        g=group.g.to_bytes(group.spec.p_bytes, "big"),
        name=group.spec.name)


def check_group_fingerprint(group, fingerprint) -> str:
    """Coordinator-side handshake check; "" if ok, else the in-band error."""
    if fingerprint and bytes(fingerprint) != group.fingerprint():
        return (f"group constants mismatch: coordinator runs group "
                f"'{group.spec.name}'; start this trustee with the same "
                f"-group")
    return ""


def check_group_constants(group, constants) -> str:
    """Trustee-side check of the coordinator's response constants; "" if
    ok (or constants absent — older coordinator), else the error."""
    if not constants or not constants.p:
        # an old-style coordinator that never populates constants skips
        # the handshake check — warn so a later opaque byte-width failure
        # is traceable to the missing negotiation, not silent
        import logging
        logging.getLogger("rpc_util").warning(
            "coordinator sent no group constants; cannot confirm it runs "
            "group '%s' — a mismatch will surface as a decode failure "
            "later", group.spec.name)
        return ""
    if (int.from_bytes(constants.p, "big") != group.p
            or int.from_bytes(constants.q, "big") != group.q
            or int.from_bytes(constants.g, "big") != group.g):
        name = constants.name or "?"
        return (f"group constants mismatch: coordinator runs group "
                f"'{name}', this trustee runs '{group.spec.name}'")
    return ""


def make_channel(url: str, max_message: int = MAX_TRUSTEE_MESSAGE,
                 keepalive_ms: int = 60_000) -> grpc.Channel:
    """Plaintext channel with the reference's size/keepalive settings."""
    return grpc.insecure_channel(url, options=[
        ("grpc.max_receive_message_length", max_message),
        ("grpc.max_send_message_length", max_message),
        ("grpc.keepalive_time_ms", keepalive_ms),
    ])


def make_server(port: int, max_message: int = MAX_TRUSTEE_MESSAGE,
                max_workers: int = 8) -> tuple[grpc.Server, int]:
    """Server on ``port`` (0 = pick a free one); returns (server, port)."""
    from concurrent import futures

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", max_message),
                 ("grpc.max_send_message_length", max_message)])
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise OSError(f"could not bind port {port}")
    return server, bound


def find_free_port() -> int:
    """Probe a free TCP port (the reference probes with ServerSocket —
    RunRemoteTrustee.java:126-136)."""
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]

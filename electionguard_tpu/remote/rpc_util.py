"""gRPC plumbing over runtime protobuf descriptors.

No generated ``*_pb2_grpc.py`` stubs exist (the image has bare protoc only,
see electionguard_tpu.publish.pb) — services and client stubs are built
directly from the service descriptors, so the .proto files remain the single
contract.  Mirrors the reference's transport settings: plaintext channels,
per-destination channel, 51 MB max message for trustee data planes and 2 KB
for registration (reference: RemoteTrusteeProxy.java:30,249-252,
RemoteKeyCeremonyProxy.java:27).
"""

from __future__ import annotations

import logging
import os
import random
import socket
from dataclasses import dataclass
from typing import Callable, Optional

import grpc
from google.protobuf import message_factory

from electionguard_tpu.obs import registry as obs_registry
from electionguard_tpu.obs import tenant as obs_tenant
from electionguard_tpu.obs import trace as obs_trace
from electionguard_tpu.publish import pb
from electionguard_tpu.testing import faults
from electionguard_tpu.utils import clock

MAX_TRUSTEE_MESSAGE = 51 * 1000 * 1000   # key exchange / batch decrypt plane
MAX_REGISTRATION_MESSAGE = 2000          # registration plane

# test seams: the chaos/retry tests record sleeps and pin the jitter;
# _sleep routes through the clock seam so backoff waits are virtual
# under the deterministic simulator
_sleep = clock.sleep
_uniform = random.uniform

# transport seam: the deterministic simulator (electionguard_tpu/sim)
# installs an in-memory transport here; None = real gRPC.  Channels and
# servers made while a transport is installed live entirely in-process.
_transport = None


def set_transport(transport) -> None:
    """Install (or with None, remove) the in-memory transport every
    subsequent make_channel/make_server call routes through."""
    global _transport
    _transport = transport


def transport():
    return _transport


# adversary seam: sim/adversary.py sets this to its wrap_server_impl
# when it is imported (the sim, or a mixfed server running the
# EGTPU_MIX_TAMPER drill).  None = honest process, no hook consulted.
_adversary_wrap: Optional[Callable[[str, Callable], Callable]] = None


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        logging.getLogger("rpc_util").warning(
            "%s=%r is not a number; using %s", name, os.environ.get(name),
            default)
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, default)))
    except ValueError:
        logging.getLogger("rpc_util").warning(
            "%s=%r is not an integer; using %s", name, os.environ.get(name),
            default)
        return default


@dataclass(frozen=True)
class RetryPolicy:
    """Transient-failure retry posture, env-tunable per process.

    The reference retries nothing (SURVEY.md §5.3); we retry the one
    status that means "peer not reachable right now" so a guardian
    restart or a slow dial-back doesn't kill a whole ceremony.
    ``EGTPU_RPC_RETRIES=1`` restores the reference's posture.

    Backoff is FULL-JITTER exponential: wait ~ U(0, min(cap, base·2^k)).
    A fixed or linear wait synchronizes retry herds — N trustees that
    lose the coordinator at the same instant would all redial at the
    same instant, forever; full jitter decorrelates them.

    ``budget`` bounds the TOTAL seconds one Stub may spend sleeping
    between retries across all its calls, so a flapping peer degrades to
    fail-fast instead of consuming every caller's deadline.
    """

    attempts: int = 3        # EGTPU_RPC_RETRIES: tries per rpc
    base_wait: float = 0.5   # EGTPU_RPC_RETRY_WAIT: backoff base (s)
    max_wait: float = 8.0    # EGTPU_RPC_RETRY_CAP: backoff ceiling (s)
    connect_window: float = 5.0   # EGTPU_RPC_CONNECT_WINDOW: max seconds
    #                               a wait_for_ready retry may block
    budget: float = 120.0    # EGTPU_RPC_RETRY_BUDGET: total backoff-sleep
    #                          seconds per Stub before fail-fast

    def backoff(self, attempt: int) -> float:
        """Full-jitter wait before retry ``attempt`` (1-based)."""
        return _uniform(0.0, min(self.max_wait,
                                 self.base_wait * (2 ** (attempt - 1))))


def retry_policy() -> RetryPolicy:
    """The env-configured policy (read per call: tests monkeypatch env)."""
    return RetryPolicy(
        attempts=_env_int("EGTPU_RPC_RETRIES", 3),
        base_wait=_env_float("EGTPU_RPC_RETRY_WAIT", 0.5),
        max_wait=_env_float("EGTPU_RPC_RETRY_CAP", 8.0),
        connect_window=_env_float("EGTPU_RPC_CONNECT_WINDOW", 5.0),
        budget=_env_float("EGTPU_RPC_RETRY_BUDGET", 120.0))


#: per-method deadline classes (defaults when Stub.call gets no timeout):
#: registration/control rpcs are tiny and answered from memory; exchange
#: legs run seconds of crypto on the production group; the data plane
#: moves 51 MB batches through device dispatches.
_DEADLINE_CLASS_OF = {
    "registerTrustee": "registration",
    "finish": "control",
    "saveState": "control",
    "getMetrics": "control",
    "health": "control",
    "sendPublicKeys": "exchange",
    "receivePublicKeys": "exchange",
    "sendSecretKeyShare": "exchange",
    "receiveSecretKeyShare": "exchange",
    "challengeShare": "exchange",
    "receiveChallengedShare": "exchange",
    "directDecrypt": "data",
    "compensatedDecrypt": "data",
    "encryptBallot": "data",
    "encryptBallotBatch": "data",
    "registerMixServer": "registration",
    "registerEncryptionWorker": "registration",
    "registerStage": "control",
    "pushRows": "data",
    "shuffleStage": "data",
    "pullRows": "data",
    "pushTelemetry": "control",
    "getFleetStatus": "control",
    "getRoot": "control",
    "getInclusionProof": "control",
    "getAuditState": "control",
}


def deadline_for(method: str) -> float:
    """Default TOTAL deadline (s) for ``method`` by its class, env-tunable
    via EGTPU_RPC_TIMEOUT_{REGISTRATION,CONTROL,EXCHANGE,DATA}."""
    cls = _DEADLINE_CLASS_OF.get(method, "exchange")
    defaults = {"registration": 30.0, "control": 30.0,
                "exchange": 120.0, "data": 600.0}
    return _env_float(f"EGTPU_RPC_TIMEOUT_{cls.upper()}", defaults[cls])


def _method_classes(method_desc):
    req = message_factory.GetMessageClass(method_desc.input_type)
    resp = message_factory.GetMessageClass(method_desc.output_type)
    return req, resp


def _default_get_metrics(request, context):
    """Registry-backed ``getMetrics`` every server answers unless it
    brings its own impl: the process's merged exposition (default
    registry + every expose()d subsystem registry)."""
    return obs_registry.merged_to_proto()


def _observe_server(service_name: str, method: str, fn: Callable) -> Callable:
    """Per-rpc server metrics into the default registry: call/error
    counts and a latency histogram per (service, method).  Always on —
    same order of cost as the serving plane's existing per-request
    metrics."""
    labels = {"service": service_name, "method": method}
    calls = obs_registry.REGISTRY.counter("rpc_server_calls_total", labels)
    errors = obs_registry.REGISTRY.counter("rpc_server_errors_total", labels)
    latency = obs_registry.REGISTRY.histogram("rpc_server_latency_ms",
                                              labels=labels)

    def observed(request, context):
        calls.inc()
        t0 = clock.monotonic()
        try:
            return fn(request, context)
        except BaseException:   # includes context.abort's control flow
            errors.inc()
            raise
        finally:
            latency.observe((clock.monotonic() - t0) * 1e3)

    return observed


def generic_service(service_name: str,
                    impls: dict[str, Callable]) -> grpc.GenericRpcHandler:
    """Build a generic handler for ``service_name`` from ``{method: fn}``
    where fn(request_msg, context) -> response_msg.

    Every impl is wrapped (inside-out) with the fault-injection hook,
    per-rpc server metrics, and — when tracing is on — a server span
    that adopts the caller's trace context from the rpc metadata.  A
    service that declares ``getMetrics`` but brings no impl gets the
    registry-backed default, so every server answers the metrics rpc.
    """
    svc = pb.service_descriptor(service_name)
    handlers = {}
    for m in svc.methods:
        fn = impls.get(m.name)
        if fn is None:
            if m.name == "getMetrics":
                fn = _default_get_metrics
            else:
                raise ValueError(
                    f"missing impl for {service_name}.{m.name}")
        req_cls, _ = _method_classes(m)
        inner = _observe_server(service_name, m.name,
                                faults.wrap_server_impl(m.name, fn))
        if _adversary_wrap is not None:
            # outermost of observe/faults: a fault-injected abort must
            # propagate PAST the adversary hook, so an attack whose
            # response never left the server is not recorded as fired
            inner = _adversary_wrap(m.name, inner)
        # tenant adoption wraps OUTSIDE the impl (and the fault/metric
        # layers) so every election_labels() resolution below runs
        # under the requesting election's scope; the trace span wraps
        # outermost so its subtree also carries the election context
        inner = obs_tenant.wrap_server_method(inner)
        wrapped = obs_trace.wrap_server_method(service_name, m.name, inner)
        handlers[m.name] = grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg: msg.SerializeToString())
    return grpc.method_handlers_generic_handler(svc.full_name, handlers)


def _is_transient(code, wfr: bool, per_try: float,
                  remaining: float) -> bool:
    """Is this failure worth a retry?  UNAVAILABLE always (peer not
    reachable right now); DEADLINE_EXCEEDED only when it expired a
    BOUNDED connect-window wait rather than the caller's own budget."""
    return (code == grpc.StatusCode.UNAVAILABLE
            or (wfr and per_try < remaining
                and code == grpc.StatusCode.DEADLINE_EXCEEDED))


class Stub:
    """Client stub for one service over one channel: ``stub.call(name, req)``."""

    def __init__(self, channel: grpc.Channel, service_name: str):
        svc = pb.service_descriptor(service_name)
        self._methods = {}
        self._retry_spent = 0.0   # cumulative backoff sleep (retry budget)
        self._metrics = {}   # per-method (calls, retries, backoff_s)
        reg = obs_registry.REGISTRY
        for m in svc.methods:
            req_cls, resp_cls = _method_classes(m)
            self._methods[m.name] = channel.unary_unary(
                f"/{svc.full_name}/{m.name}",
                request_serializer=lambda msg: msg.SerializeToString(),
                response_deserializer=resp_cls.FromString)
            # retries were invisible unless a fault-plan audit log was
            # active; now every Stub records per-method call/retry/
            # backoff counts, labeled with the deadline class (bound
            # once here — the call hot path only touches Counter.inc)
            labels = {"method": m.name,
                      "class": _DEADLINE_CLASS_OF.get(m.name, "exchange")}
            self._metrics[m.name] = (
                reg.counter("rpc_client_calls_total", labels),
                reg.counter("rpc_client_retries_total", labels),
                reg.counter("rpc_client_backoff_seconds_total", labels))

    def call(self, method: str, request, timeout: Optional[float] = None,
             policy: Optional[RetryPolicy] = None):
        """One rpc with a TOTAL deadline of ``timeout`` seconds (None =
        the method's deadline class, see ``deadline_for``).

        UNAVAILABLE (transport-level) is retried with FULL-JITTER
        exponential backoff while deadline, attempts, and the Stub's
        retry budget all hold.  Retries pass ``wait_for_ready`` so the
        channel actually re-dials a peer that is coming (back) up
        instead of failing fast inside gRPC's own reconnect backoff
        window — but each such wait is BOUNDED (``connect_window``) so a
        permanently-dead peer fails in seconds, not the whole deadline.
        Safe because every service method is idempotent: the
        batch/exchange rpcs are pure functions of the request (plus
        fresh randomness), and both coordinators treat a same-identity
        re-registration as idempotent.
        """
        pol = policy if policy is not None else retry_policy()
        if timeout is None:
            timeout = deadline_for(method)
        calls, retries, backoff_s = self._metrics[method]
        calls.inc()
        deadline = clock.monotonic() + timeout
        attempt = 0
        while True:
            remaining = deadline - clock.monotonic()
            wfr = attempt > 0
            per_try = max(0.001, min(remaining, pol.connect_window)
                          if wfr else remaining)
            try:
                return self._methods[method](
                    request, timeout=per_try, wait_for_ready=wfr)
            except grpc.RpcError as e:
                attempt += 1
                code = e.code()
                # a DEADLINE on a BOUNDED connect-wait means "still not
                # reachable" — transient like UNAVAILABLE; a deadline on
                # a full-budget attempt is a real timeout
                transient = _is_transient(code, wfr=wfr, per_try=per_try,
                                          remaining=remaining)
                if not transient or attempt >= pol.attempts:
                    obs_registry.REGISTRY.counter(
                        "rpc_client_failures_total",
                        {"method": method,
                         "code": code.name if code else "UNKNOWN"}).inc()
                    raise
                wait = pol.backoff(attempt)
                if (deadline - clock.monotonic() <= wait
                        or self._retry_spent + wait > pol.budget):
                    obs_registry.REGISTRY.counter(
                        "rpc_client_failures_total",
                        {"method": method,
                         "code": code.name if code else "UNKNOWN"}).inc()
                    raise
                retries.inc()
                backoff_s.inc(wait)
                self._retry_spent += wait
                _sleep(wait)


def group_constants_msg(group):
    """The coordinator's GroupConstants for registration responses."""
    return pb.msg("GroupConstants")(
        p=group.p.to_bytes(group.spec.p_bytes, "big"),
        q=group.q.to_bytes(group.spec.q_bytes, "big"),
        g=group.g.to_bytes(group.spec.p_bytes, "big"),
        name=group.spec.name)


def check_group_fingerprint(group, fingerprint,
                            boundary: str = "registration") -> str:
    """Coordinator-side handshake check; "" if ok, else the in-band
    error — routed through the ingestion gate so a wrong-group peer is
    rejected with the named ``validate.group_mismatch`` class and the
    sim's detection log sees it."""
    from electionguard_tpu.crypto import validate as vgate
    err = vgate.gate_fingerprint(group, bytes(fingerprint or b""), boundary)
    if err:
        return (f"{err}; coordinator runs group '{group.spec.name}' — "
                f"start this peer with the same -group")
    return ""


def check_group_constants(group, constants) -> str:
    """Trustee-side check of the coordinator's response constants; "" if
    ok (or constants absent — older coordinator), else the error."""
    if not constants or not constants.p:
        # an old-style coordinator that never populates constants skips
        # the handshake check — warn so a later opaque byte-width failure
        # is traceable to the missing negotiation, not silent
        import logging
        logging.getLogger("rpc_util").warning(
            "coordinator sent no group constants; cannot confirm it runs "
            "group '%s' — a mismatch will surface as a decode failure "
            "later", group.spec.name)
        return ""
    if (int.from_bytes(constants.p, "big") != group.p
            or int.from_bytes(constants.q, "big") != group.q
            or int.from_bytes(constants.g, "big") != group.g):
        name = constants.name or "?"
        return (f"group constants mismatch: coordinator runs group "
                f"'{name}', this trustee runs '{group.spec.name}'")
    return ""


def make_channel(url: str, max_message: int = MAX_TRUSTEE_MESSAGE,
                 keepalive_ms: int = 60_000) -> grpc.Channel:
    """Plaintext channel with the reference's size/keepalive settings.
    When a fault plan is active (EGTPU_FAULT_PLAN / faults.install), the
    channel is wrapped with the plan's client interceptor; when tracing
    is on (EGTPU_OBS_TRACE / obs.trace.enable), the trace interceptor
    wraps OUTSIDE the fault one, so client spans see injected faults as
    the real rpc outcomes they simulate.  Both are identity when off.

    Under an installed sim transport the channel is in-memory; the sim
    channel applies the active fault plan's client rules itself
    (grpc.intercept_channel needs a real grpc.Channel)."""
    if _transport is not None:
        return _transport.channel(url, max_message)
    return obs_trace.intercept_channel(
        obs_tenant.intercept_channel(
            faults.intercept_channel(grpc.insecure_channel(url, options=[
                ("grpc.max_receive_message_length", max_message),
                ("grpc.max_send_message_length", max_message),
                ("grpc.keepalive_time_ms", keepalive_ms),
            ]))))


def make_plain_channel(url: str, max_message: int = MAX_TRUSTEE_MESSAGE,
                       keepalive_ms: int = 60_000) -> grpc.Channel:
    """Channel WITHOUT the fault/trace interceptors: the obs-plane escape
    hatch.  Telemetry pushes must observe injected faults, not suffer
    them, and must not trace themselves (each client span export would
    trigger another push — unbounded recursion)."""
    if _transport is not None:
        return _transport.channel(url, max_message, plain=True)
    return grpc.insecure_channel(url, options=[
        ("grpc.max_receive_message_length", max_message),
        ("grpc.max_send_message_length", max_message),
        ("grpc.keepalive_time_ms", keepalive_ms),
    ])


def make_server(port: int, max_message: int = MAX_TRUSTEE_MESSAGE,
                max_workers: int = 8) -> tuple[grpc.Server, int]:
    """Server on ``port`` (0 = pick a free one); returns (server, port)."""
    if _transport is not None:
        return _transport.server(port, max_message)
    from concurrent import futures

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", max_message),
                 ("grpc.max_send_message_length", max_message)])
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise OSError(f"could not bind port {port}")
    return server, bound


def find_free_port() -> int:
    """Probe a free TCP port (the reference probes with ServerSocket —
    RunRemoteTrustee.java:126-136)."""
    if _transport is not None:
        return _transport.free_port()
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]

"""Framed-stream primitives: ONE torn-frame policy for the whole repo.

Every durable stream in the record directory is framed as a 4-byte
big-endian length header + payload bytes.  Before this module, three
places each re-implemented the "what does an incomplete tail mean"
decision — ``publisher.repair_frame_stream`` (crash recovery),
``Consumer``'s slice readers (ingestion), and ``serve.journal.replay``
(its JSON-lines analogue).  They now share a single policy:

* **torn tail** — the stream ends mid-header or mid-payload.  That is
  the expected shape of a SIGKILL during an append (or of a reader
  racing a writer): the unfinished frame was never acknowledged, so it
  is *retryable* — recovery truncates it, a tailer waits for the rest.
* **corrupt frame** — a header that cannot be a frame at all (length
  above the sanity bound).  No amount of waiting completes it; readers
  must go red immediately with an attributable named error.

``TruncatedFrameError`` / ``CorruptFrameError`` subclass ``IOError`` so
pre-existing ``except IOError`` call sites keep working, and carry
``utils.errors`` class tokens (``[publish.truncated_frame]``,
``[publish.corrupt_frame]``) so the sim's soundness oracle can attribute
ingestion rejections to the framing defense that fired.

``FramedTailer`` is the incremental face of the same policy: it follows
a stream that is still being written, yielding each frame exactly once
and treating a torn tail as "not yet", which is what the live
verification plane (``verify/live``) is built on.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Optional

from electionguard_tpu.utils import errors

HEADER_LEN = 4
#: sanity bound on a single frame: anything larger than this is not a
#: torn write, it is garbage in the header (no record message comes
#: within orders of magnitude of it) — overridable per-reader
DEFAULT_MAX_FRAME = 64 << 20


class FramingError(IOError):
    """Base for framed-stream decode failures (an ``IOError`` so legacy
    ``except IOError`` recovery paths keep catching it)."""


class TruncatedFrameError(FramingError):
    """Stream ends mid-frame: a torn tail (retryable — the write never
    completed, or the writer is still appending)."""

    CLS = "publish.truncated_frame"

    def __init__(self, msg: str):
        super().__init__(errors.named(self.CLS, msg))


class CorruptFrameError(FramingError):
    """A frame header that cannot be valid (length over the sanity
    bound): unrecoverable, the reader must go red."""

    CLS = "publish.corrupt_frame"

    def __init__(self, msg: str):
        super().__init__(errors.named(self.CLS, msg))


def write_frame(f, data: bytes) -> None:
    f.write(struct.pack(">I", len(data)))
    f.write(data)


def read_frames_slice(path: str, offset: int = 0,
                      count: int | None = None,
                      max_frame: int = DEFAULT_MAX_FRAME
                      ) -> Iterator[bytes]:
    """Decode frames from ``offset``: exactly ``count`` of them, or to
    EOF when ``count`` is None — the ONE definition of the framing."""
    with open(path, "rb") as f:
        f.seek(offset)
        remaining = count
        while remaining is None or remaining > 0:
            hdr = f.read(HEADER_LEN)
            if not hdr and remaining is None:
                return
            if len(hdr) != HEADER_LEN:
                raise TruncatedFrameError(
                    f"truncated frame header in {path}")
            (n,) = struct.unpack(">I", hdr)
            if n > max_frame:
                raise CorruptFrameError(
                    f"frame length {n} exceeds sanity bound "
                    f"{max_frame} in {path}")
            data = f.read(n)
            if len(data) != n:
                raise TruncatedFrameError(f"truncated frame in {path}")
            yield data
            if remaining is not None:
                remaining -= 1


def read_frames(path: str) -> Iterator[bytes]:
    return read_frames_slice(path)


def scan_frame_shards(path: str,
                      n_shards: int) -> list[tuple[int, int, int]]:
    """Split a framed stream into ≤ n_shards contiguous ``(byte_offset,
    frame_count, last_frame_offset)`` slices by reading only the 4-byte
    length headers — file-offset slicing, no payload decode (README
    §Scaling model: the election record is a framed stream, so sharding
    it across feeder processes is offset arithmetic).  The last-frame
    offset lets a coordinator decode exactly ONE boundary ballot per
    shard (its confirmation code seeds the next feeder's V6 chain)."""
    offsets: list[int] = []
    with open(path, "rb") as f:
        pos = 0
        while True:
            hdr = f.read(HEADER_LEN)
            if not hdr:
                break
            if len(hdr) != HEADER_LEN:
                raise TruncatedFrameError(
                    f"truncated frame header in {path}")
            (n,) = struct.unpack(">I", hdr)
            offsets.append(pos)
            pos += HEADER_LEN + n
            f.seek(pos)
    total = len(offsets)
    if total == 0:
        return []
    per = -(-total // n_shards)  # ceil
    return [(offsets[i], min(per, total - i),
             offsets[min(i + per, total) - 1])
            for i in range(0, total, per)]


def repair_frame_stream(path: str) -> tuple[int, Optional[bytes]]:
    """Truncate a framed stream to its last COMPLETE frame (a SIGKILL can
    tear the final write) and return ``(n_frames, last_frame_bytes)``.
    The one frame decode the caller needs for chain continuity (the last
    ballot's confirmation code) comes back without re-reading the file."""
    if not os.path.exists(path):
        return 0, None
    n = 0
    last: Optional[bytes] = None
    good_end = 0
    with open(path, "rb") as f:
        while True:
            hdr = f.read(HEADER_LEN)
            if len(hdr) < HEADER_LEN:
                break
            (size,) = struct.unpack(">I", hdr)
            data = f.read(size)
            if len(data) != size:
                break
            n += 1
            last = data
            good_end += HEADER_LEN + size
    actual = os.path.getsize(path)
    if actual != good_end:
        with open(path, "r+b") as f:
            f.truncate(good_end)
    return n, last


def complete_lines(data: bytes) -> tuple[list[bytes], bytes]:
    """The JSON-lines face of the torn-tail policy: split a byte blob
    into its COMPLETE (newline-terminated) lines plus the torn tail
    (bytes after the last newline — a mid-append crash, or a writer the
    reader is racing).  Empty lines are dropped; the tail is returned
    verbatim so a tailer can retry once the writer finishes it."""
    if not data:
        return [], b""
    body, sep, tail = data.rpartition(b"\n")
    lines = [ln for ln in body.split(b"\n") if ln] if sep else []
    return lines, tail


class FramedTailer:
    """Incremental reader over a framed stream that is still being
    written.  ``poll()`` returns every frame that has fully landed since
    the last call and advances the cursor past them; a torn tail (header
    or payload not yet complete) is simply left for the next poll.  A
    header over the sanity bound raises ``CorruptFrameError`` — that is
    never a partial write, the stream itself is bad.

    The cursor (``offset``/``frames``) is plain state, so a checkpointed
    consumer can persist it and resume a fresh tailer exactly where the
    killed one stopped."""

    def __init__(self, path: str, offset: int = 0, frames: int = 0,
                 max_frame: int = DEFAULT_MAX_FRAME):
        self.path = path
        self.offset = int(offset)     # byte offset of the next frame
        self.frames = int(frames)     # frames consumed so far
        self.max_frame = int(max_frame)

    def poll(self) -> list[bytes]:
        """All newly COMPLETE frames past the cursor ([] when the file
        does not exist yet or only a torn tail landed)."""
        if not os.path.exists(self.path):
            return []
        out: list[bytes] = []
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            while True:
                hdr = f.read(HEADER_LEN)
                if len(hdr) < HEADER_LEN:
                    break   # torn header: retry next poll
                (n,) = struct.unpack(">I", hdr)
                if n > self.max_frame:
                    raise CorruptFrameError(
                        f"frame length {n} exceeds sanity bound "
                        f"{self.max_frame} at byte {self.offset} "
                        f"in {self.path}")
                data = f.read(n)
                if len(data) != n:
                    break   # torn payload: retry next poll
                out.append(data)
                self.offset += HEADER_LEN + n
                self.frames += 1
        return out

    def torn_bytes(self) -> int:
        """Bytes sitting past the cursor that never completed a frame —
        0 on a cleanly closed stream, >0 exactly when the writer died
        mid-append (matches what ``repair_frame_stream`` would cut)."""
        if not os.path.exists(self.path):
            return 0
        return max(0, os.path.getsize(self.path) - self.offset)

"""Runtime-generated protobuf message classes.

The image ships bare ``protoc`` (no grpcio-tools) and a protobuf 6.x Python
runtime that rejects 3.x gencode — so instead of checked-in ``*_pb2.py`` we
compile the .proto files to a ``FileDescriptorSet`` (``descriptors.pb``,
regenerated automatically when the protos change) and materialize message
classes through ``message_factory`` at import time.  gRPC services are built
from the same descriptors with hand-rolled method handlers
(electionguard_tpu.remote), so the .proto files remain the single wire
contract — mirroring the reference where the protos define the protocol
(reference: src/main/proto/*.proto).
"""

from __future__ import annotations

import os
import subprocess

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

_PROTO_DIR = os.path.join(os.path.dirname(__file__), "proto")
_DESC_PATH = os.path.join(_PROTO_DIR, "descriptors.pb")
_PROTO_FILES = ["common.proto", "election_record.proto", "remote_rpc.proto"]


def _compile_descriptors() -> None:
    try:
        subprocess.run(
            ["protoc", f"--descriptor_set_out={_DESC_PATH}",
             "--include_imports", "-I", _PROTO_DIR] + _PROTO_FILES,
            check=True, cwd=_PROTO_DIR)
    except FileNotFoundError:
        # no protoc on PATH: compile with the in-tree pure-Python
        # fallback (publish/protoc_mini.py) — same descriptors, same
        # wire bytes, covers exactly the grammar these files use
        from electionguard_tpu.publish import protoc_mini
        texts = []
        for name in _PROTO_FILES:
            with open(os.path.join(_PROTO_DIR, name)) as f:
                texts.append((name, f.read()))
        fds = protoc_mini.compile_files(texts)
        with open(_DESC_PATH, "wb") as f:
            f.write(fds.SerializeToString())


def _ensure_descriptors() -> bytes:
    protos = [os.path.join(_PROTO_DIR, f) for f in _PROTO_FILES]
    stale = (not os.path.exists(_DESC_PATH) or
             any(os.path.getmtime(p) > os.path.getmtime(_DESC_PATH)
                 for p in protos))
    if stale:
        _compile_descriptors()
    with open(_DESC_PATH, "rb") as f:
        return f.read()


_fds = descriptor_pb2.FileDescriptorSet()
_fds.ParseFromString(_ensure_descriptors())
POOL = descriptor_pool.DescriptorPool()
for _f in _fds.file:
    POOL.Add(_f)

_messages = message_factory.GetMessageClassesForFiles(
    [f.name for f in _fds.file], POOL)


def msg(name: str):
    """Message class by short name, e.g. msg('ElementModP')."""
    return _messages[f"electionguard_tpu.{name}"]


def service_descriptor(name: str):
    return POOL.FindServiceByName(f"electionguard_tpu.{name}")


# commonly used classes, bound once
ElementModP = msg("ElementModP")
ElementModQ = msg("ElementModQ")
UInt256 = msg("UInt256")
ElGamalCiphertext = msg("ElGamalCiphertext")
GenericChaumPedersenProof = msg("GenericChaumPedersenProof")
DisjunctiveChaumPedersenProof = msg("DisjunctiveChaumPedersenProof")
ConstantChaumPedersenProof = msg("ConstantChaumPedersenProof")
HashedElGamalCiphertext = msg("HashedElGamalCiphertext")
SchnorrProof = msg("SchnorrProof")
GuardianRecord = msg("GuardianRecord")
ElectionInitialized = msg("ElectionInitialized")
EncryptedSelection = msg("EncryptedSelection")
EncryptedContest = msg("EncryptedContest")
EncryptedBallot = msg("EncryptedBallot")
EncryptedTallySelection = msg("EncryptedTallySelection")
EncryptedTallyContest = msg("EncryptedTallyContest")
EncryptedTally = msg("EncryptedTally")
TallyResult = msg("TallyResult")
CompensatedShare = msg("CompensatedShare")
PartialDecryption = msg("PartialDecryption")
PlaintextTallySelection = msg("PlaintextTallySelection")
PlaintextTallyContest = msg("PlaintextTallyContest")
PlaintextTally = msg("PlaintextTally")
DecryptingGuardian = msg("DecryptingGuardian")
DecryptionResult = msg("DecryptionResult")
MixRow = msg("MixRow")
MixProof = msg("MixProof")
MixStageHeader = msg("MixStageHeader")
RegisterMixServerRequest = msg("RegisterMixServerRequest")
RegisterMixServerResponse = msg("RegisterMixServerResponse")
MixStageRequest = msg("MixStageRequest")
MixStageReady = msg("MixStageReady")
MixRowChunk = msg("MixRowChunk")
MixRowRequest = msg("MixRowRequest")
MixShuffleRequest = msg("MixShuffleRequest")
MixStageResult = msg("MixStageResult")
RegisterEncryptionWorkerRequest = msg("RegisterEncryptionWorkerRequest")
RegisterEncryptionWorkerResponse = msg("RegisterEncryptionWorkerResponse")
ObsHeartbeat = msg("ObsHeartbeat")
TelemetryBatch = msg("TelemetryBatch")
TelemetryAck = msg("TelemetryAck")
FleetStatusRequest = msg("FleetStatusRequest")
FleetProcess = msg("FleetProcess")
FleetStatusResponse = msg("FleetStatusResponse")
BulletinRootRequest = msg("BulletinRootRequest")
BulletinRootResponse = msg("BulletinRootResponse")
InclusionProofRequest = msg("InclusionProofRequest")
InclusionProofResponse = msg("InclusionProofResponse")
AuditStateRequest = msg("AuditStateRequest")
AuditStateResponse = msg("AuditStateResponse")

"""Election record data model: config, initialization, tally/decryption
results.

Native replacement for the reference's [ext] record types
(``ElectionInitialized``, ``TallyResult``, ``DecryptionResult``,
``DecryptingGuardian`` — imported at RunRemoteDecryptor.java:9-21, published
at RunRemoteKeyCeremony.java:224-228 and RunRemoteDecryptor.java:306-321).
The record directory layout and (de)serialization live in
``electionguard_tpu.publish.publisher``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from electionguard_tpu.ballot.manifest import Manifest
from electionguard_tpu.ballot.tally import EncryptedTally, PlaintextTally
from electionguard_tpu.core.group import ElementModP, ElementModQ
from electionguard_tpu.crypto.schnorr import SchnorrProof


@dataclass(frozen=True)
class ElectionConfig:
    """Manifest + ceremony parameters (what the key ceremony consumes)."""

    manifest: Manifest
    n_guardians: int
    quorum: int

    def __post_init__(self):
        if not (1 <= self.quorum <= self.n_guardians):
            raise ValueError("require 1 <= quorum <= n_guardians")


@dataclass(frozen=True)
class GuardianRecord:
    """Public record of one guardian (commitments + proofs)."""

    guardian_id: str
    x_coordinate: int
    coefficient_commitments: tuple[ElementModP, ...]
    coefficient_proofs: tuple[SchnorrProof, ...]


@dataclass(frozen=True)
class ElectionInitialized:
    """Published after the key ceremony
    (reference: RunRemoteKeyCeremony.java:224-228)."""

    config: ElectionConfig
    joint_public_key: ElementModP       # K = Π K_i0
    manifest_hash: bytes
    crypto_base_hash: ElementModQ       # Q
    extended_base_hash: ElementModQ     # Q̄ = H(Q, K)
    guardians: tuple[GuardianRecord, ...]
    metadata: dict[str, str] = field(default_factory=dict)

    def guardian(self, guardian_id: str) -> Optional[GuardianRecord]:
        for g in self.guardians:
            if g.guardian_id == guardian_id:
                return g
        return None


@dataclass(frozen=True)
class TallyResult:
    """Encrypted tally + the initialization it was accumulated under."""

    election_init: ElectionInitialized
    encrypted_tally: EncryptedTally
    tally_ids: tuple[str, ...] = ()
    metadata: dict[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class DecryptingGuardian:
    """A guardian that participated in decryption, with its Lagrange
    coefficient (reference [ext] ``DecryptingGuardian``,
    RunRemoteDecryptor.java:296-304)."""

    guardian_id: str
    x_coordinate: int
    lagrange_coefficient: ElementModQ


@dataclass(frozen=True)
class DecryptionResult:
    """Published after decryption
    (reference: RunRemoteDecryptor.java:306-321)."""

    tally_result: TallyResult
    decrypted_tally: PlaintextTally
    decrypting_guardians: tuple[DecryptingGuardian, ...]
    metadata: dict[str, str] = field(default_factory=dict)


@dataclass
class ElectionRecord:
    """Everything a phase reads/writes: the record directory *is* the
    checkpoint system (SURVEY.md §5.4).  Later phases may be None."""

    election_init: ElectionInitialized
    encrypted_ballots: list = field(default_factory=list)
    tally_result: Optional[TallyResult] = None
    decryption_result: Optional[DecryptionResult] = None
    spoiled_ballot_tallies: list = field(default_factory=list)
    mix_stages: list = field(default_factory=list)  # mixnet.stage.MixStage
    # fabric: signed per-shard manifests of a merged record (empty =
    # single-worker record; fabric.manifest.ShardManifest)
    shard_manifests: list = field(default_factory=list)

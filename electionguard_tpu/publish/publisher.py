"""Publisher/Consumer: the election-record directory store.

Native replacement for the reference's [ext] ``Publisher(dir, mode)`` /
``Consumer(dir, group)`` surface (``writeElectionInitialized``,
``writeDecryptionResult``, ``writeTrustee``, ``writePlaintextBallot``;
``electionRecordFromConsumer``, ``readElectionInitialized``,
``readTallyResult``, ``iterateSpoiledBallots`` — call sites:
RunRemoteKeyCeremony.java:106-110,188-193,224, RunRemoteDecryptor.java:112-127,
237,265, RunRemoteTrustee.java:329).

The record directory IS the checkpoint system (SURVEY.md §5.4): each phase
reads its predecessor's artifacts and writes its own.  Layout::

    <dir>/election_initialized.pb
    <dir>/encrypted_ballots.pb          length-prefixed EncryptedBallot stream
    <dir>/tally_result.pb
    <dir>/decryption_result.pb
    <dir>/spoiled_ballot_tallies.pb     length-prefixed PlaintextTally stream
    <dir>/plaintext_ballots/*.json      input staging
    <dir>/invalid_ballots/*.json
    <trustee_dir>/trustee-<id>.json     PRIVATE guardian state (kept outside
                                        the public record, like the
                                        reference's -out trustee dir)

Streams are framed as 4-byte big-endian length + message bytes, so million-
ballot records stream without loading everything in memory.
"""

from __future__ import annotations

import os
from typing import Iterator

from electionguard_tpu.ballot.ciphertext import EncryptedBallot
from electionguard_tpu.ballot.plaintext import PlaintextBallot
from electionguard_tpu.ballot.tally import PlaintextTally
from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.publish import framing, pb, serialize
from electionguard_tpu.publish.election_record import (DecryptionResult,
                                                       ElectionInitialized,
                                                       ElectionRecord,
                                                       TallyResult)
from electionguard_tpu.utils import errors

_INIT = "election_initialized.pb"
_BALLOTS = "encrypted_ballots.pb"
_TALLY = "tally_result.pb"
_DECRYPTION = "decryption_result.pb"
_SPOILED = "spoiled_ballot_tallies.pb"
_MIX_FMT = "mix_stage_{:03d}.pb"   # framed: header frame + n_rows MixRow

# The framing itself (header layout, torn-tail policy, shard scan,
# crash repair) lives in ``publish.framing`` — one policy shared with
# journal recovery and the live-verification tailer.  These aliases keep
# the long-standing import surface of this module stable.
_write_frame = framing.write_frame
_read_frames_slice = framing.read_frames_slice
_read_frames = framing.read_frames
scan_frame_shards = framing.scan_frame_shards
repair_frame_stream = framing.repair_frame_stream


class Publisher:
    """Writes phase artifacts.  ``create_new=True`` mirrors the reference's
    fail-fast ``validateOutputDir`` (RunRemoteKeyCeremony.java:188-193)."""

    def __init__(self, out_dir: str, create_new: bool = False):
        if create_new and os.path.exists(out_dir) and os.listdir(out_dir):
            raise FileExistsError(
                f"output dir {out_dir} exists and is not empty")
        os.makedirs(out_dir, exist_ok=True)
        if not os.access(out_dir, os.W_OK):
            raise PermissionError(f"output dir {out_dir} not writable")
        self.dir = out_dir

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def write_election_initialized(self, init: ElectionInitialized):
        with open(self._path(_INIT), "wb") as f:
            f.write(serialize.publish_election_initialized(
                init).SerializeToString())

    def write_encrypted_ballots(self, ballots) -> int:
        with self.open_encrypted_ballots() as stream:
            for b in ballots:
                stream.write(b)
            return stream.n

    def open_encrypted_ballots(self,
                               append: bool = False
                               ) -> "EncryptedBallotStream":
        """Incremental framed writer: callers encrypting chunk-by-chunk
        write each chunk and drop it, keeping host memory O(chunk).
        ``append=True`` continues an existing stream (crash recovery:
        repair the tail with ``repair_frame_stream`` first)."""
        return EncryptedBallotStream(self._path(_BALLOTS), append=append)

    def write_tally_result(self, tally: TallyResult):
        with open(self._path(_TALLY), "wb") as f:
            f.write(serialize.publish_tally_result(tally).SerializeToString())

    def write_decryption_result(self, result: DecryptionResult):
        with open(self._path(_DECRYPTION), "wb") as f:
            f.write(serialize.publish_decryption_result(
                result).SerializeToString())

    def write_spoiled_ballot_tallies(self, tallies) -> int:
        n = 0
        with open(self._path(_SPOILED), "wb") as f:
            for t in tallies:
                _write_frame(f, serialize.publish_plaintext_tally(
                    t).SerializeToString())
                n += 1
        return n

    def write_shard_manifests(self, manifests) -> str:
        """Fabric: publish the signed per-shard manifests of a merged
        record next to the concatenated ballot stream."""
        from electionguard_tpu.fabric import manifest as fab_manifest
        return fab_manifest.write_shard_manifests(self.dir, manifests)

    def write_plaintext_ballot(self, subdir: str, ballot: PlaintextBallot):
        d = self._path(subdir)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, f"{ballot.ballot_id}.json"), "w") as f:
            f.write(ballot.to_json())

    def write_mix_stage(self, group: GroupContext, stage) -> str:
        """Publish one mix stage as a framed, fsync'd stream: frame 0 is
        the MixStageHeader (binding hash + proof transcript), then
        ``n_rows`` MixRow frames — the same durable framing discipline
        as the encrypted-ballot stream, so stages survive a crash with
        at worst a truncated (detectable) tail."""
        path = self._path(_MIX_FMT.format(stage.stage_index))
        with open(path, "wb") as f:
            _write_frame(f, serialize.publish_mix_header(
                group, stage).SerializeToString())
            for row_a, row_b in zip(stage.pads, stage.datas):
                _write_frame(f, serialize.publish_mix_row(
                    group, row_a, row_b).SerializeToString())
            f.flush()
            os.fsync(f.fileno())
        return path


class EncryptedBallotStream:
    """Appending framed EncryptedBallot writer (see Publisher.open_encrypted_ballots)."""

    def __init__(self, path: str, append: bool = False):
        self._f = open(path, "ab" if append else "wb")
        self.n = 0

    def write(self, ballot: EncryptedBallot):
        _write_frame(self._f, serialize.publish_encrypted_ballot(
            ballot).SerializeToString())
        self.n += 1

    def flush(self) -> None:
        """Make every written frame durable (flush + fsync).  The serving
        plane calls this once per drained batch: "published" is then a
        well-defined on-disk state the crash-recovery replay can diff the
        admission journal against."""
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class Consumer:
    """Reads phase artifacts back (group-validating on import)."""

    def __init__(self, in_dir: str, group: GroupContext):
        if not os.path.isdir(in_dir):
            raise FileNotFoundError(f"record dir {in_dir} does not exist")
        self.dir = in_dir
        self.group = group

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def has_election_initialized(self) -> bool:
        return os.path.exists(self._path(_INIT))

    def read_election_initialized(self) -> ElectionInitialized:
        m = pb.ElectionInitialized()
        with open(self._path(_INIT), "rb") as f:
            m.ParseFromString(f.read())
        return serialize.import_election_initialized(self.group, m)

    def iterate_encrypted_ballots(self) -> Iterator[EncryptedBallot]:
        path = self._path(_BALLOTS)
        if not os.path.exists(path):
            return iter(())
        return self.iterate_encrypted_ballots_slice(0, None)

    def ballot_shards(self, n_shards: int) -> list[tuple[int, int, int]]:
        """Contiguous (byte_offset, count, last_frame_offset) slices of
        the encrypted-ballot stream for ≤ n_shards feeder processes
        (header scan only)."""
        path = self._path(_BALLOTS)
        if not os.path.exists(path):
            return []
        return scan_frame_shards(path, n_shards)

    def iterate_encrypted_ballots_slice(
            self, offset: int,
            count: int | None) -> Iterator[EncryptedBallot]:
        """Decode one feeder's slice (from ``ballot_shards``); count=None
        reads to EOF."""
        for frame in _read_frames_slice(self._path(_BALLOTS), offset,
                                        count):
            m = pb.EncryptedBallot()
            m.ParseFromString(frame)
            yield serialize.import_encrypted_ballot(self.group, m)

    def read_tally_result(self) -> TallyResult:
        m = pb.TallyResult()
        with open(self._path(_TALLY), "rb") as f:
            m.ParseFromString(f.read())
        return serialize.import_tally_result(self.group, m)

    def has_tally_result(self) -> bool:
        return os.path.exists(self._path(_TALLY))

    def read_decryption_result(self) -> DecryptionResult:
        m = pb.DecryptionResult()
        with open(self._path(_DECRYPTION), "rb") as f:
            m.ParseFromString(f.read())
        return serialize.import_decryption_result(self.group, m)

    def has_decryption_result(self) -> bool:
        return os.path.exists(self._path(_DECRYPTION))

    def iterate_spoiled_ballot_tallies(self) -> Iterator[PlaintextTally]:
        path = self._path(_SPOILED)
        if not os.path.exists(path):
            return
        for frame in _read_frames(path):
            m = pb.PlaintextTally()
            m.ParseFromString(frame)
            yield serialize.import_plaintext_tally(self.group, m)

    def mix_stage_count(self) -> int:
        """Contiguous published mix stages (stage files must be densely
        numbered from 0; a gap ends the cascade)."""
        n = 0
        while os.path.exists(self._path(_MIX_FMT.format(n))):
            n += 1
        return n

    def has_mix_stages(self) -> bool:
        return self.mix_stage_count() > 0

    def read_mix_stage(self, k: int):
        """Decode one published stage (header + all rows resident — a
        stage is O(cast ballots), the mix plane's working set)."""
        from electionguard_tpu.mixnet.stage import MixStage
        path = self._path(_MIX_FMT.format(k))
        frames = _read_frames(path)
        hm = pb.MixStageHeader()
        try:
            hm.ParseFromString(next(frames))
        except StopIteration:
            raise framing.TruncatedFrameError(
                f"mix stage {k}: stream {path} has no header frame")
        proof = serialize.import_mix_proof(self.group, hm.proof)
        pads, datas = [], []
        for frame in frames:
            rm = pb.MixRow()
            rm.ParseFromString(frame)
            row_a, row_b = serialize.import_mix_row(self.group, rm)
            pads.append(row_a)
            datas.append(row_b)
        if len(pads) != int(hm.n_rows):
            raise framing.FramingError(errors.named(
                "publish.mix_row_mismatch",
                f"mix stage {k}: {len(pads)} row frames != "
                f"header n_rows {int(hm.n_rows)}"))
        return MixStage(int(hm.stage_index), int(hm.n_rows),
                        int(hm.width), serialize.import_u256(hm.input_hash),
                        pads, datas, proof)

    def read_mix_stages(self) -> list:
        return [self.read_mix_stage(k) for k in range(self.mix_stage_count())]

    def read_shard_manifests(self) -> list:
        """Fabric: the signed per-shard manifests of a merged record
        ([] = single-worker record)."""
        from electionguard_tpu.fabric import manifest as fab_manifest
        return fab_manifest.read_shard_manifests(self.dir)

    def has_shard_manifests(self) -> bool:
        from electionguard_tpu.fabric import manifest as fab_manifest
        return os.path.exists(self._path(fab_manifest.MANIFESTS_NAME))

    def iterate_plaintext_ballots(self, subdir: str) -> Iterator[PlaintextBallot]:
        d = self._path(subdir)
        if not os.path.isdir(d):
            return
        for name in sorted(os.listdir(d)):
            if name.endswith(".json"):
                with open(os.path.join(d, name)) as f:
                    yield PlaintextBallot.from_json(f.read())


def election_record_from_consumer(consumer: Consumer) -> ElectionRecord:
    """Mirror of the reference's [ext] ``electionRecordFromConsumer``
    (RunRemoteKeyCeremony.java:106)."""
    record = ElectionRecord(consumer.read_election_initialized())
    record.encrypted_ballots = list(consumer.iterate_encrypted_ballots())
    if consumer.has_tally_result():
        record.tally_result = consumer.read_tally_result()
    if consumer.has_decryption_result():
        record.decryption_result = consumer.read_decryption_result()
    record.spoiled_ballot_tallies = list(
        consumer.iterate_spoiled_ballot_tallies())
    record.shard_manifests = consumer.read_shard_manifests()
    return record

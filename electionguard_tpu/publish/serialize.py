"""Dataclass ↔ proto converters: the framework's ConvertCommonProto.

Native replacement for the reference's codec
(src/main/java/electionguard/util/ConvertCommonProto.java:23-153): paired
``import_*`` (proto → domain, validating) and ``publish_*`` (domain → proto)
functions for every wire type.  Big-endian unsigned byte encodings, 512/32
bytes wide (reference: common.proto:6-16, ConvertCommonProto.java:46,55).
"""

from __future__ import annotations

from electionguard_tpu.ballot.ciphertext import (BallotState, EncryptedBallot,
                                                 EncryptedContest,
                                                 EncryptedSelection)
from electionguard_tpu.ballot.manifest import Manifest
from electionguard_tpu.ballot.tally import (EncryptedTally,
                                            EncryptedTallyContest,
                                            EncryptedTallySelection,
                                            PartialDecryption,
                                            PlaintextTally,
                                            PlaintextTallyContest,
                                            PlaintextTallySelection)
from electionguard_tpu.core.group import (ElementModP, ElementModQ,
                                          GroupContext)
from electionguard_tpu.crypto.chaum_pedersen import (
    ConstantChaumPedersenProof, DisjunctiveChaumPedersenProof,
    GenericChaumPedersenProof)
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
from electionguard_tpu.crypto.hashed_elgamal import HashedElGamalCiphertext
from electionguard_tpu.crypto.schnorr import SchnorrProof
from electionguard_tpu.decrypt.interface import CompensatedDecryptionAndProof
from electionguard_tpu.publish import pb
from electionguard_tpu.publish.election_record import (DecryptingGuardian,
                                                       DecryptionResult,
                                                       ElectionConfig,
                                                       ElectionInitialized,
                                                       GuardianRecord,
                                                       TallyResult)

# ---------------------------------------------------------------------------
# crypto primitives
# ---------------------------------------------------------------------------


def publish_p(e: ElementModP):
    return pb.ElementModP(value=e.to_bytes())


def import_p(g: GroupContext, m) -> ElementModP:
    if len(m.value) != g.spec.p_bytes:
        raise ValueError(f"ElementModP wire width {len(m.value)} != "
                         f"{g.spec.p_bytes}")
    return g.bytes_to_p(m.value)


def publish_q(e: ElementModQ):
    return pb.ElementModQ(value=e.to_bytes())


def import_q(g: GroupContext, m) -> ElementModQ:
    if len(m.value) != g.spec.q_bytes:
        raise ValueError(f"ElementModQ wire width {len(m.value)} != "
                         f"{g.spec.q_bytes}")
    return g.bytes_to_q(m.value)


def publish_ciphertext(c: ElGamalCiphertext):
    return pb.ElGamalCiphertext(pad=publish_p(c.pad), data=publish_p(c.data))


def import_ciphertext(g: GroupContext, m) -> ElGamalCiphertext:
    return ElGamalCiphertext(import_p(g, m.pad), import_p(g, m.data))


def publish_generic_proof(p: GenericChaumPedersenProof):
    return pb.GenericChaumPedersenProof(
        challenge=publish_q(p.challenge), response=publish_q(p.response))


def import_generic_proof(g: GroupContext, m) -> GenericChaumPedersenProof:
    return GenericChaumPedersenProof(
        import_q(g, m.challenge), import_q(g, m.response))


def publish_disjunctive_proof(p: DisjunctiveChaumPedersenProof):
    return pb.DisjunctiveChaumPedersenProof(
        proof_zero_challenge=publish_q(p.proof_zero_challenge),
        proof_zero_response=publish_q(p.proof_zero_response),
        proof_one_challenge=publish_q(p.proof_one_challenge),
        proof_one_response=publish_q(p.proof_one_response))


def import_disjunctive_proof(g: GroupContext, m) -> DisjunctiveChaumPedersenProof:
    return DisjunctiveChaumPedersenProof(
        import_q(g, m.proof_zero_challenge),
        import_q(g, m.proof_zero_response),
        import_q(g, m.proof_one_challenge),
        import_q(g, m.proof_one_response))


def publish_constant_proof(p: ConstantChaumPedersenProof):
    return pb.ConstantChaumPedersenProof(
        challenge=publish_q(p.challenge), response=publish_q(p.response),
        constant=p.constant)


def import_constant_proof(g: GroupContext, m) -> ConstantChaumPedersenProof:
    return ConstantChaumPedersenProof(
        import_q(g, m.challenge), import_q(g, m.response), int(m.constant))


def publish_hashed_ciphertext(h: HashedElGamalCiphertext):
    return pb.HashedElGamalCiphertext(
        c0=publish_p(h.c0), c1=h.c1, c2=publish_u256(h.c2),
        num_bytes=h.num_bytes)


def import_hashed_ciphertext(g: GroupContext, m) -> HashedElGamalCiphertext:
    return HashedElGamalCiphertext(
        import_p(g, m.c0), bytes(m.c1), import_u256(m.c2),
        int(m.num_bytes))


def publish_schnorr(p: SchnorrProof):
    # the proof travels as (challenge, response) only — the key rides in
    # the parallel coefficient_commitments list (reference contract,
    # common.proto:37-41)
    return pb.SchnorrProof(challenge=publish_q(p.challenge),
                           response=publish_q(p.response))


def import_schnorr(g: GroupContext, m, public_key) -> SchnorrProof:
    """``public_key``: the ElementModP from the parallel commitments list
    this proof attests to (not on the wire — the reference reserves its
    field)."""
    return SchnorrProof(public_key,
                        import_q(g, m.challenge), import_q(g, m.response))


def publish_u256(b: bytes):
    if len(b) != 32:
        raise ValueError("UInt256 must be exactly 32 bytes")
    return pb.UInt256(value=b)


def import_u256(m) -> bytes:
    if len(m.value) != 32:
        raise ValueError("UInt256 must be exactly 32 bytes")
    return bytes(m.value)


# ---------------------------------------------------------------------------
# election record
# ---------------------------------------------------------------------------


def publish_guardian_record(r: GuardianRecord):
    return pb.GuardianRecord(
        guardian_id=r.guardian_id, x_coordinate=r.x_coordinate,
        coefficient_commitments=[publish_p(k)
                                 for k in r.coefficient_commitments],
        coefficient_proofs=[publish_schnorr(p)
                            for p in r.coefficient_proofs])


def import_guardian_record(g: GroupContext, m) -> GuardianRecord:
    if len(m.coefficient_commitments) != len(m.coefficient_proofs):
        raise ValueError(
            f"guardian {m.guardian_id}: {len(m.coefficient_commitments)} "
            f"commitments vs {len(m.coefficient_proofs)} proofs — each "
            f"proof needs its parallel commitment as public key")
    commitments = tuple(import_p(g, k) for k in m.coefficient_commitments)
    return GuardianRecord(
        guardian_id=m.guardian_id, x_coordinate=int(m.x_coordinate),
        coefficient_commitments=commitments,
        coefficient_proofs=tuple(
            import_schnorr(g, p, k)
            for p, k in zip(m.coefficient_proofs, commitments)))


def publish_election_initialized(e: ElectionInitialized):
    return pb.ElectionInitialized(
        manifest_json=e.config.manifest.to_json(),
        n_guardians=e.config.n_guardians,
        quorum=e.config.quorum,
        joint_public_key=publish_p(e.joint_public_key),
        manifest_hash=publish_u256(e.manifest_hash),
        crypto_base_hash=publish_q(e.crypto_base_hash),
        extended_base_hash=publish_q(e.extended_base_hash),
        guardians=[publish_guardian_record(r) for r in e.guardians],
        metadata=dict(e.metadata))


def import_election_initialized(g: GroupContext, m) -> ElectionInitialized:
    return ElectionInitialized(
        config=ElectionConfig(Manifest.from_json(m.manifest_json),
                              int(m.n_guardians), int(m.quorum)),
        joint_public_key=import_p(g, m.joint_public_key),
        manifest_hash=import_u256(m.manifest_hash),
        crypto_base_hash=import_q(g, m.crypto_base_hash),
        extended_base_hash=import_q(g, m.extended_base_hash),
        guardians=tuple(import_guardian_record(g, r) for r in m.guardians),
        metadata=dict(m.metadata))


def publish_encrypted_ballot(b: EncryptedBallot):
    return pb.EncryptedBallot(
        ballot_id=b.ballot_id, ballot_style_id=b.ballot_style_id,
        manifest_hash=publish_u256(b.manifest_hash),
        code_seed=publish_u256(b.code_seed), code=publish_u256(b.code),
        timestamp=b.timestamp,
        contests=[pb.EncryptedContest(
            contest_id=c.contest_id, sequence_order=c.sequence_order,
            selections=[pb.EncryptedSelection(
                selection_id=s.selection_id,
                sequence_order=s.sequence_order,
                ciphertext=publish_ciphertext(s.ciphertext),
                proof=publish_disjunctive_proof(s.proof),
                is_placeholder=s.is_placeholder)
                for s in c.selections],
            proof=publish_constant_proof(c.proof))
            for c in b.contests],
        state=pb.EncryptedBallot.BallotState.Value(b.state.value))


def import_encrypted_ballot(g: GroupContext, m) -> EncryptedBallot:
    return EncryptedBallot(
        ballot_id=m.ballot_id, ballot_style_id=m.ballot_style_id,
        manifest_hash=import_u256(m.manifest_hash),
        code_seed=import_u256(m.code_seed), code=import_u256(m.code),
        timestamp=int(m.timestamp),
        contests=tuple(EncryptedContest(
            contest_id=c.contest_id, sequence_order=int(c.sequence_order),
            selections=tuple(EncryptedSelection(
                selection_id=s.selection_id,
                sequence_order=int(s.sequence_order),
                ciphertext=import_ciphertext(g, s.ciphertext),
                proof=import_disjunctive_proof(g, s.proof),
                is_placeholder=bool(s.is_placeholder))
                for s in c.selections),
            proof=import_constant_proof(g, c.proof))
            for c in m.contests),
        state=BallotState(
            pb.EncryptedBallot.BallotState.Name(m.state)))


def publish_encrypted_tally(t: EncryptedTally):
    return pb.EncryptedTally(
        tally_id=t.tally_id,
        contests=[pb.EncryptedTallyContest(
            contest_id=c.contest_id, sequence_order=c.sequence_order,
            selections=[pb.EncryptedTallySelection(
                selection_id=s.selection_id,
                sequence_order=s.sequence_order,
                ciphertext=publish_ciphertext(s.ciphertext))
                for s in c.selections])
            for c in t.contests],
        cast_ballot_count=t.cast_ballot_count)


def import_encrypted_tally(g: GroupContext, m) -> EncryptedTally:
    return EncryptedTally(
        tally_id=m.tally_id,
        contests=tuple(EncryptedTallyContest(
            contest_id=c.contest_id, sequence_order=int(c.sequence_order),
            selections=tuple(EncryptedTallySelection(
                selection_id=s.selection_id,
                sequence_order=int(s.sequence_order),
                ciphertext=import_ciphertext(g, s.ciphertext))
                for s in c.selections))
            for c in m.contests),
        cast_ballot_count=int(m.cast_ballot_count))


def publish_tally_result(t: TallyResult):
    return pb.TallyResult(
        election_init=publish_election_initialized(t.election_init),
        encrypted_tally=publish_encrypted_tally(t.encrypted_tally),
        tally_ids=list(t.tally_ids), metadata=dict(t.metadata))


def import_tally_result(g: GroupContext, m) -> TallyResult:
    return TallyResult(
        election_init=import_election_initialized(g, m.election_init),
        encrypted_tally=import_encrypted_tally(g, m.encrypted_tally),
        tally_ids=tuple(m.tally_ids), metadata=dict(m.metadata))


def publish_plaintext_tally(t: PlaintextTally):
    def pub_share(sh: PartialDecryption):
        m = pb.PartialDecryption(guardian_id=sh.guardian_id,
                                 share=publish_p(sh.share))
        if sh.proof is not None:
            m.proof.CopyFrom(publish_generic_proof(sh.proof))
        if sh.recovered_parts:
            for tid, part in sorted(sh.recovered_parts.items()):
                m.recovered_parts.append(pb.CompensatedShare(
                    trustee_id=tid,
                    partial_decryption=publish_p(part.partial_decryption),
                    proof=publish_generic_proof(part.proof),
                    recovered_public_key_share=publish_p(
                        part.recovered_public_key_share)))
        return m

    return pb.PlaintextTally(
        tally_id=t.tally_id,
        contests=[pb.PlaintextTallyContest(
            contest_id=c.contest_id,
            selections=[pb.PlaintextTallySelection(
                selection_id=s.selection_id, tally=s.tally,
                value=publish_p(s.value),
                message=publish_ciphertext(s.message),
                shares=[pub_share(sh) for sh in s.shares])
                for s in c.selections])
            for c in t.contests])


def import_plaintext_tally(g: GroupContext, m) -> PlaintextTally:
    def imp_share(sm) -> PartialDecryption:
        proof = (import_generic_proof(g, sm.proof)
                 if sm.HasField("proof") else None)
        parts = None
        if sm.recovered_parts:
            parts = {
                p.trustee_id: CompensatedDecryptionAndProof(
                    import_p(g, p.partial_decryption),
                    import_generic_proof(g, p.proof),
                    import_p(g, p.recovered_public_key_share))
                for p in sm.recovered_parts}
        return PartialDecryption(sm.guardian_id, import_p(g, sm.share),
                                 proof, parts)

    return PlaintextTally(
        tally_id=m.tally_id,
        contests=tuple(PlaintextTallyContest(
            contest_id=c.contest_id,
            selections=tuple(PlaintextTallySelection(
                selection_id=s.selection_id, tally=int(s.tally),
                value=import_p(g, s.value),
                message=import_ciphertext(g, s.message),
                shares=tuple(imp_share(sh) for sh in s.shares))
                for s in c.selections))
            for c in m.contests))


def publish_decryption_result(d: DecryptionResult):
    return pb.DecryptionResult(
        tally_result=publish_tally_result(d.tally_result),
        decrypted_tally=publish_plaintext_tally(d.decrypted_tally),
        decrypting_guardians=[pb.DecryptingGuardian(
            guardian_id=a.guardian_id, x_coordinate=a.x_coordinate,
            lagrange_coefficient=publish_q(a.lagrange_coefficient))
            for a in d.decrypting_guardians],
        metadata=dict(d.metadata))


def import_decryption_result(g: GroupContext, m) -> DecryptionResult:
    return DecryptionResult(
        tally_result=import_tally_result(g, m.tally_result),
        decrypted_tally=import_plaintext_tally(g, m.decrypted_tally),
        decrypting_guardians=tuple(DecryptingGuardian(
            guardian_id=a.guardian_id, x_coordinate=int(a.x_coordinate),
            lagrange_coefficient=import_q(g, a.lagrange_coefficient))
            for a in m.decrypting_guardians),
        metadata=dict(m.metadata))


# ---------------------------------------------------------------------------
# mixnet plane (publish/consume MixStage streams — mixnet/stage.py)
# ---------------------------------------------------------------------------


def _pub_p_int(g: GroupContext, v: int):
    """Int-valued ElementModP (the mixnet plane works in plain ints)."""
    return pb.ElementModP(value=v.to_bytes(g.spec.p_bytes, "big"))


def _imp_p_int(g: GroupContext, m) -> int:
    return import_p(g, m).value  # width + range validated


def _pub_q_int(g: GroupContext, v: int):
    return pb.ElementModQ(value=v.to_bytes(g.spec.q_bytes, "big"))


def _imp_q_int(g: GroupContext, m) -> int:
    return import_q(g, m).value


def publish_mix_proof(g: GroupContext, pr):
    return pb.MixProof(
        permutation_commitments=[_pub_p_int(g, v)
                                 for v in pr.permutation_commitments],
        chain_commitments=[_pub_p_int(g, v) for v in pr.chain_commitments],
        t1=_pub_p_int(g, pr.t1), t2=_pub_p_int(g, pr.t2),
        t3=_pub_p_int(g, pr.t3),
        t41=[_pub_p_int(g, v) for v in pr.t41],
        t42=[_pub_p_int(g, v) for v in pr.t42],
        that=[_pub_p_int(g, v) for v in pr.that],
        challenge=_pub_q_int(g, pr.challenge),
        v1=_pub_q_int(g, pr.v1), v2=_pub_q_int(g, pr.v2),
        v3=_pub_q_int(g, pr.v3),
        v4=[_pub_q_int(g, v) for v in pr.v4],
        vhat=[_pub_q_int(g, v) for v in pr.vhat],
        vprime=[_pub_q_int(g, v) for v in pr.vprime])


def import_mix_proof(g: GroupContext, m):
    from electionguard_tpu.mixnet.proof import MixProof
    return MixProof(
        permutation_commitments=tuple(_imp_p_int(g, v)
                                      for v in m.permutation_commitments),
        chain_commitments=tuple(_imp_p_int(g, v)
                                for v in m.chain_commitments),
        t1=_imp_p_int(g, m.t1), t2=_imp_p_int(g, m.t2),
        t3=_imp_p_int(g, m.t3),
        t41=tuple(_imp_p_int(g, v) for v in m.t41),
        t42=tuple(_imp_p_int(g, v) for v in m.t42),
        that=tuple(_imp_p_int(g, v) for v in m.that),
        challenge=_imp_q_int(g, m.challenge),
        v1=_imp_q_int(g, m.v1), v2=_imp_q_int(g, m.v2),
        v3=_imp_q_int(g, m.v3),
        v4=tuple(_imp_q_int(g, v) for v in m.v4),
        vhat=tuple(_imp_q_int(g, v) for v in m.vhat),
        vprime=tuple(_imp_q_int(g, v) for v in m.vprime))


def publish_mix_header(g: GroupContext, stage):
    return pb.MixStageHeader(
        stage_index=stage.stage_index, n_rows=stage.n_rows,
        width=stage.width, input_hash=publish_u256(stage.input_hash),
        proof=publish_mix_proof(g, stage.proof))


def publish_mix_row(g: GroupContext, row_pads, row_datas):
    return pb.MixRow(ciphertexts=[
        pb.ElGamalCiphertext(pad=_pub_p_int(g, a), data=_pub_p_int(g, b))
        for a, b in zip(row_pads, row_datas)])


def import_mix_row(g: GroupContext, m) -> tuple[list, list]:
    pads = [_imp_p_int(g, c.pad) for c in m.ciphertexts]
    datas = [_imp_p_int(g, c.data) for c in m.ciphertexts]
    return pads, datas


# ---------------------------------------------------------------------------
# serving plane (plaintext ballots over the wire — serve/service.py)
# ---------------------------------------------------------------------------


def publish_plaintext_ballot(b):
    """PlaintextBallot dataclass -> wire message (the serving rpc's
    request payload; distinct from Publisher.write_plaintext_ballot's
    JSON staging form)."""
    return pb.msg("PlaintextBallot")(
        ballot_id=b.ballot_id, ballot_style_id=b.ballot_style_id,
        contests=[pb.msg("PlaintextContest")(
            contest_id=c.contest_id,
            selections=[pb.msg("PlaintextSelection")(
                selection_id=s.selection_id, vote=s.vote)
                for s in c.selections])
            for c in b.contests])


def import_plaintext_ballot(m):
    from electionguard_tpu.ballot.plaintext import (PlaintextBallot,
                                                    PlaintextBallotContest,
                                                    PlaintextBallotSelection)
    return PlaintextBallot(
        ballot_id=m.ballot_id, ballot_style_id=m.ballot_style_id,
        contests=tuple(PlaintextBallotContest(
            contest_id=c.contest_id,
            selections=tuple(PlaintextBallotSelection(
                s.selection_id, int(s.vote)) for s in c.selections))
            for c in m.contests))

"""Pure-Python .proto → FileDescriptorSet compiler (protoc fallback).

``pb.py`` regenerates ``descriptors.pb`` whenever a .proto changes.  The
original path shells out to ``protoc``; some environments (including the
one this repo grows in) ship the protobuf *runtime* but no compiler at
all.  This module compiles the repo's protos to
``descriptor_pb2.FileDescriptorSet`` directly, covering exactly the
grammar the three contract files use:

    proto3 syntax, package, imports, messages (scalar / message /
    repeated / map fields, nested enums and messages, reserved ranges),
    top-level enums, and services with unary rpcs.

It is NOT a general protoc replacement — unsupported constructs raise
``ProtoParseError`` loudly so a future .proto edit that outgrows the
subset fails at build time, not with silently wrong descriptors.  Wire
bytes are produced by the protobuf runtime from these descriptors, so
byte compatibility is unaffected by which compiler built them.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from google.protobuf import descriptor_pb2

_SCALARS = {
    "double": descriptor_pb2.FieldDescriptorProto.TYPE_DOUBLE,
    "float": descriptor_pb2.FieldDescriptorProto.TYPE_FLOAT,
    "int64": descriptor_pb2.FieldDescriptorProto.TYPE_INT64,
    "uint64": descriptor_pb2.FieldDescriptorProto.TYPE_UINT64,
    "int32": descriptor_pb2.FieldDescriptorProto.TYPE_INT32,
    "fixed64": descriptor_pb2.FieldDescriptorProto.TYPE_FIXED64,
    "fixed32": descriptor_pb2.FieldDescriptorProto.TYPE_FIXED32,
    "bool": descriptor_pb2.FieldDescriptorProto.TYPE_BOOL,
    "string": descriptor_pb2.FieldDescriptorProto.TYPE_STRING,
    "bytes": descriptor_pb2.FieldDescriptorProto.TYPE_BYTES,
    "uint32": descriptor_pb2.FieldDescriptorProto.TYPE_UINT32,
    "sint32": descriptor_pb2.FieldDescriptorProto.TYPE_SINT32,
    "sint64": descriptor_pb2.FieldDescriptorProto.TYPE_SINT64,
}

_LABEL_OPTIONAL = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
_LABEL_REPEATED = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
_TYPE_MESSAGE = descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
_TYPE_ENUM = descriptor_pb2.FieldDescriptorProto.TYPE_ENUM

_TOKEN = re.compile(r"""
    "(?:[^"\\]|\\.)*"      |   # string literal
    [A-Za-z_][\w.]*        |   # identifier (possibly dotted)
    \d+                    |   # integer
    [{}=;<>,()\[\]]            # punctuation
""", re.VERBOSE)


class ProtoParseError(Exception):
    pass


def _tokenize(text: str) -> list[str]:
    # strip // line and /* block */ comments before tokenizing
    text = re.sub(r"//[^\n]*", "", text)
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.DOTALL)
    pos, tokens = 0, []
    for m in _TOKEN.finditer(text):
        between = text[pos:m.start()]
        if between.strip():
            raise ProtoParseError(f"unrecognized input: {between.strip()!r}")
        tokens.append(m.group(0))
        pos = m.end()
    if text[pos:].strip():
        raise ProtoParseError(f"trailing input: {text[pos:].strip()!r}")
    return tokens


class _Cursor:
    def __init__(self, tokens: list[str]):
        self.tokens = tokens
        self.i = 0

    def peek(self) -> str | None:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def next(self) -> str:
        tok = self.peek()
        if tok is None:
            raise ProtoParseError("unexpected end of file")
        self.i += 1
        return tok

    def expect(self, want: str) -> str:
        tok = self.next()
        if tok != want:
            raise ProtoParseError(f"expected {want!r}, got {tok!r}")
        return tok


@dataclass
class _Scope:
    """Symbol table entry: fully-qualified name -> is_enum."""

    names: dict[str, bool] = field(default_factory=dict)

    def add(self, fq: str, is_enum: bool):
        self.names[fq] = is_enum


def _parse_enum(cur: _Cursor, enum_proto) -> None:
    enum_proto.name = cur.next()
    cur.expect("{")
    while cur.peek() != "}":
        name = cur.next()
        if name == "option":  # e.g. allow_alias — skip to ';'
            while cur.next() != ";":
                pass
            continue
        cur.expect("=")
        number = int(cur.next())
        cur.expect(";")
        enum_proto.value.add(name=name, number=number)
    cur.expect("}")


def _parse_reserved(cur: _Cursor, msg) -> None:
    # `reserved 1, 2;` / `reserved 1 to 5;` (names unsupported — unused)
    while True:
        start = cur.next()
        if not start.isdigit():
            raise ProtoParseError(f"reserved names unsupported: {start!r}")
        start = int(start)
        end = start
        if cur.peek() == "to":
            cur.next()
            end = int(cur.next())
        msg.reserved_range.add(start=start, end=end + 1)  # end exclusive
        tok = cur.next()
        if tok == ";":
            return
        if tok != ",":
            raise ProtoParseError(f"expected , or ; in reserved, got {tok!r}")


def _parse_field(cur: _Cursor, first: str, msg, scope_prefix: str) -> None:
    label = _LABEL_OPTIONAL
    proto3_optional = False
    type_name = first
    if first in ("repeated", "optional"):
        if first == "repeated":
            label = _LABEL_REPEATED
        else:
            proto3_optional = True
        type_name = cur.next()
    if type_name == "map":
        _parse_map_field(cur, msg, scope_prefix)
        return
    name = cur.next()
    cur.expect("=")
    number = int(cur.next())
    if cur.peek() == "[":  # field options — skip to ']'
        while cur.next() != "]":
            pass
    cur.expect(";")
    f = msg.field.add(name=name, number=number, label=label)
    if proto3_optional:
        f.proto3_optional = True
        # proto3 optional needs a synthetic oneof
        f.oneof_index = len(msg.oneof_decl)
        msg.oneof_decl.add(name=f"_{name}")
    if type_name in _SCALARS:
        f.type = _SCALARS[type_name]
    else:
        f.type_name = type_name  # resolved in a second pass


def _snake_to_camel(s: str) -> str:
    return "".join(p.capitalize() for p in s.split("_"))


def _parse_map_field(cur: _Cursor, msg, scope_prefix: str) -> None:
    cur.expect("<")
    key_type = cur.next()
    cur.expect(",")
    val_type = cur.next()
    cur.expect(">")
    name = cur.next()
    cur.expect("=")
    number = int(cur.next())
    cur.expect(";")
    if key_type not in _SCALARS or key_type in ("double", "float", "bytes"):
        raise ProtoParseError(f"invalid map key type {key_type!r}")
    entry = msg.nested_type.add(name=f"{_snake_to_camel(name)}Entry")
    entry.options.map_entry = True
    entry.field.add(name="key", number=1, label=_LABEL_OPTIONAL,
                    type=_SCALARS[key_type])
    v = entry.field.add(name="value", number=2, label=_LABEL_OPTIONAL)
    if val_type in _SCALARS:
        v.type = _SCALARS[val_type]
    else:
        v.type_name = val_type
    f = msg.field.add(name=name, number=number, label=_LABEL_REPEATED,
                      type=_TYPE_MESSAGE)
    f.type_name = f"{scope_prefix}.{entry.name}"


def _parse_message(cur: _Cursor, msg, scope_prefix: str) -> None:
    msg.name = cur.next()
    fq = f"{scope_prefix}.{msg.name}"
    cur.expect("{")
    while True:
        tok = cur.next()
        if tok == "}":
            return
        if tok == "enum":
            _parse_enum(cur, msg.enum_type.add())
        elif tok == "message":
            _parse_message(cur, msg.nested_type.add(), fq)
        elif tok == "reserved":
            _parse_reserved(cur, msg)
        elif tok == "option":
            while cur.next() != ";":
                pass
        elif tok == "oneof":
            raise ProtoParseError("oneof unsupported by protoc_mini")
        else:
            _parse_field(cur, tok, msg, fq)


def _parse_service(cur: _Cursor, svc) -> None:
    svc.name = cur.next()
    cur.expect("{")
    while True:
        tok = cur.next()
        if tok == "}":
            return
        if tok != "rpc":
            raise ProtoParseError(f"expected rpc in service, got {tok!r}")
        m = svc.method.add(name=cur.next())
        cur.expect("(")
        if cur.peek() == "stream":
            raise ProtoParseError("streaming rpcs unsupported")
        m.input_type = cur.next()
        cur.expect(")")
        cur.expect("returns")
        cur.expect("(")
        if cur.peek() == "stream":
            raise ProtoParseError("streaming rpcs unsupported")
        m.output_type = cur.next()
        cur.expect(")")
        tok = cur.next()
        if tok == "{":
            cur.expect("}")
            if cur.peek() == ";":
                cur.next()
        elif tok != ";":
            raise ProtoParseError(f"expected {{}} or ; after rpc, got {tok!r}")


def parse_file(name: str, text: str) -> descriptor_pb2.FileDescriptorProto:
    f = descriptor_pb2.FileDescriptorProto(name=name)
    cur = _Cursor(_tokenize(text))
    while cur.peek() is not None:
        tok = cur.next()
        if tok == "syntax":
            cur.expect("=")
            syntax = cur.next().strip('"')
            if syntax != "proto3":
                raise ProtoParseError(f"only proto3 supported: {syntax}")
            f.syntax = syntax
            cur.expect(";")
        elif tok == "package":
            f.package = cur.next()
            cur.expect(";")
        elif tok == "import":
            dep = cur.next()
            if dep in ("public", "weak"):
                dep = cur.next()
            f.dependency.append(dep.strip('"'))
            cur.expect(";")
        elif tok == "option":
            while cur.next() != ";":
                pass
        elif tok == "message":
            _parse_message(cur, f.message_type.add(), f".{f.package}"
                           if f.package else "")
        elif tok == "enum":
            _parse_enum(cur, f.enum_type.add())
        elif tok == "service":
            _parse_service(cur, f.service.add())
        else:
            raise ProtoParseError(f"unexpected top-level token {tok!r}")
    return f


# ---------------------------------------------------------------------------
# type resolution
# ---------------------------------------------------------------------------


def _collect_symbols(files) -> dict[str, bool]:
    """{fully-qualified name: is_enum} across the whole file set."""
    symbols: dict[str, bool] = {}

    def walk_msg(prefix: str, msg):
        fq = f"{prefix}.{msg.name}"
        symbols[fq] = False
        for e in msg.enum_type:
            symbols[f"{fq}.{e.name}"] = True
        for n in msg.nested_type:
            walk_msg(fq, n)

    for f in files:
        prefix = f".{f.package}" if f.package else ""
        for m in f.message_type:
            walk_msg(prefix, m)
        for e in f.enum_type:
            symbols[f"{prefix}.{e.name}"] = True
    return symbols


def _resolve_name(name: str, scope: str, symbols: dict[str, bool]) -> str:
    """protoc's scoping rule, simplified: try the innermost enclosing
    scope outward, then the bare package-qualified name."""
    if name.startswith("."):
        if name not in symbols:
            raise ProtoParseError(f"unknown type {name}")
        return name
    parts = scope.split(".") if scope else []
    while parts:
        candidate = ".".join(parts) + f".{name}"
        if candidate in symbols:
            return candidate
        parts.pop()
    candidate = f".{name}"
    if candidate in symbols:
        return candidate
    raise ProtoParseError(f"cannot resolve type {name!r} in scope {scope!r}")


def _resolve_fields(msg, scope: str, symbols: dict[str, bool]) -> None:
    fq = f"{scope}.{msg.name}"
    for f in msg.field:
        if f.type_name and not f.type_name.startswith("."):
            f.type_name = _resolve_name(f.type_name, fq, symbols)
        if f.type_name and f.type == 0:
            f.type = _TYPE_ENUM if symbols[f.type_name] else _TYPE_MESSAGE
    for n in msg.nested_type:
        _resolve_fields(n, fq, symbols)


def compile_files(named_texts: list[tuple[str, str]]
                  ) -> descriptor_pb2.FileDescriptorSet:
    """[(file_name, proto_text)] -> FileDescriptorSet, dependency-ordered
    as given (imports must precede importers, like protoc's -I output)."""
    fds = descriptor_pb2.FileDescriptorSet()
    files = [parse_file(name, text) for name, text in named_texts]
    symbols = _collect_symbols(files)
    for f in files:
        prefix = f".{f.package}" if f.package else ""
        for m in f.message_type:
            _resolve_fields(m, prefix, symbols)
        for s in f.service:
            for m in s.method:
                m.input_type = _resolve_name(m.input_type, prefix, symbols)
                m.output_type = _resolve_name(m.output_type, prefix, symbols)
        fds.file.append(f)
    return fds
